/* Native engine fast path — CPython C-API implementations of the
 * per-row hot loops of the incremental engine (profiling: freeze_row,
 * consolidate and key-byte building dominate the Python engine's
 * wordcount profile). The reference keeps these loops in Rust
 * (src/engine/dataflow.rs arrangements, value.rs key hashing); here they
 * are a C extension bound through pathway_tpu.native.
 *
 * Exposed functions:
 *   consolidate(deltas)        -> list[(key,row,diff)] summed, zero-dropped
 *   freeze_rows(rows)          -> list of hashable stand-ins (fast path:
 *                                 row already hashable -> returned as-is)
 *   value_bytes(args_tuple)    -> bytes — the injective length-prefixed
 *                                 serialization behind ref_scalar
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <math.h>
#include <stdint.h>
#include <string.h>

/* -- helpers ----------------------------------------------------------- */

static PyObject *freeze_value_py = NULL; /* python fallback for exotic values */

static PyObject *
freeze_one(PyObject *v)
{
    /* fast path: hashable scalars pass through unchanged */
    Py_hash_t h = PyObject_Hash(v);
    if (h != -1 || !PyErr_Occurred()) {
        Py_INCREF(v);
        return v;
    }
    PyErr_Clear();
    if (freeze_value_py == NULL) {
        PyObject *mod = PyImport_ImportModule("pathway_tpu.engine.stream");
        if (mod == NULL)
            return NULL;
        freeze_value_py = PyObject_GetAttrString(mod, "freeze_value");
        Py_DECREF(mod);
        if (freeze_value_py == NULL)
            return NULL;
    }
    return PyObject_CallOneArg(freeze_value_py, v);
}

static PyObject *
freeze_row_c(PyObject *row)
{
    Py_hash_t h = PyObject_Hash(row);
    if (h != -1 || !PyErr_Occurred()) {
        Py_INCREF(row);
        return row;
    }
    PyErr_Clear();
    if (!PyTuple_Check(row)) {
        return freeze_one(row);
    }
    Py_ssize_t n = PyTuple_GET_SIZE(row);
    PyObject *out = PyTuple_New(n);
    if (out == NULL)
        return NULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *fv = freeze_one(PyTuple_GET_ITEM(row, i));
        if (fv == NULL) {
            Py_DECREF(out);
            return NULL;
        }
        PyTuple_SET_ITEM(out, i, fv);
    }
    return out;
}

/* -- consolidate -------------------------------------------------------- */

static PyObject *
fast_consolidate(PyObject *self, PyObject *arg)
{
    PyObject *seq = PySequence_Fast(arg, "consolidate expects a sequence");
    if (seq == NULL)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);

    /* ident(key, frozen_row) -> [key, row, diff] */
    PyObject *acc = PyDict_New();
    PyObject *order = PyList_New(0); /* deterministic output order */
    if (acc == NULL || order == NULL)
        goto fail;

    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *delta = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(delta) || PyTuple_GET_SIZE(delta) != 3) {
            PyErr_SetString(PyExc_TypeError,
                            "delta must be (key, row, diff)");
            goto fail;
        }
        PyObject *key = PyTuple_GET_ITEM(delta, 0);
        PyObject *row = PyTuple_GET_ITEM(delta, 1);
        PyObject *diff = PyTuple_GET_ITEM(delta, 2);

        PyObject *frow = freeze_row_c(row);
        if (frow == NULL)
            goto fail;
        PyObject *ident = PyTuple_Pack(2, key, frow);
        Py_DECREF(frow);
        if (ident == NULL)
            goto fail;

        PyObject *slot = PyDict_GetItemWithError(acc, ident);
        if (slot == NULL && PyErr_Occurred()) {
            Py_DECREF(ident);
            goto fail;
        }
        if (slot == NULL) {
            slot = PyList_New(3);
            if (slot == NULL) {
                Py_DECREF(ident);
                goto fail;
            }
            Py_INCREF(key);
            PyList_SET_ITEM(slot, 0, key);
            Py_INCREF(row);
            PyList_SET_ITEM(slot, 1, row);
            Py_INCREF(diff);
            PyList_SET_ITEM(slot, 2, diff);
            if (PyDict_SetItem(acc, ident, slot) < 0 ||
                PyList_Append(order, slot) < 0) {
                Py_DECREF(slot);
                Py_DECREF(ident);
                goto fail;
            }
            Py_DECREF(slot);
        } else {
            PyObject *cur = PyList_GET_ITEM(slot, 2);
            PyObject *sum = PyNumber_Add(cur, diff);
            if (sum == NULL) {
                Py_DECREF(ident);
                goto fail;
            }
            PyList_SetItem(slot, 2, sum); /* steals sum */
        }
        Py_DECREF(ident);
    }

    PyObject *result = PyList_New(0);
    if (result == NULL)
        goto fail;
    Py_ssize_t m = PyList_GET_SIZE(order);
    for (Py_ssize_t i = 0; i < m; i++) {
        PyObject *slot = PyList_GET_ITEM(order, i);
        PyObject *diff = PyList_GET_ITEM(slot, 2);
        int nz = PyObject_IsTrue(diff);
        if (nz < 0) {
            Py_DECREF(result);
            goto fail;
        }
        if (nz) {
            PyObject *t = PyTuple_Pack(3, PyList_GET_ITEM(slot, 0),
                                       PyList_GET_ITEM(slot, 1), diff);
            if (t == NULL || PyList_Append(result, t) < 0) {
                Py_XDECREF(t);
                Py_DECREF(result);
                goto fail;
            }
            Py_DECREF(t);
        }
    }
    Py_DECREF(acc);
    Py_DECREF(order);
    Py_DECREF(seq);
    return result;

fail:
    Py_XDECREF(acc);
    Py_XDECREF(order);
    Py_DECREF(seq);
    return NULL;
}

/* -- freeze_rows -------------------------------------------------------- */

static PyObject *
fast_freeze_rows(PyObject *self, PyObject *arg)
{
    PyObject *seq = PySequence_Fast(arg, "freeze_rows expects a sequence");
    if (seq == NULL)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject *out = PyList_New(n);
    if (out == NULL) {
        Py_DECREF(seq);
        return NULL;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *f = freeze_row_c(PySequence_Fast_GET_ITEM(seq, i));
        if (f == NULL) {
            Py_DECREF(out);
            Py_DECREF(seq);
            return NULL;
        }
        PyList_SET_ITEM(out, i, f);
    }
    Py_DECREF(seq);
    return out;
}

/* -- value_bytes: injective serialization for ref_scalar ---------------- */

typedef struct {
    char *buf;
    Py_ssize_t len;
    Py_ssize_t cap;
} Buf;

static int
buf_ensure(Buf *b, Py_ssize_t extra)
{
    if (b->len + extra <= b->cap)
        return 0;
    Py_ssize_t ncap = b->cap * 2;
    while (ncap < b->len + extra)
        ncap *= 2;
    char *nb = PyMem_Realloc(b->buf, ncap);
    if (nb == NULL) {
        PyErr_NoMemory();
        return -1;
    }
    b->buf = nb;
    b->cap = ncap;
    return 0;
}

static int
buf_put(Buf *b, const void *data, Py_ssize_t n)
{
    if (buf_ensure(b, n) < 0)
        return -1;
    memcpy(b->buf + b->len, data, n);
    b->len += n;
    return 0;
}

static int
buf_put_u32(Buf *b, uint32_t v)
{
    /* explicit little-endian: key bytes must be identical to the Python
     * path's struct.pack('<I') on every host (api.py requires keys stable
     * across processes for persistence / multi-host determinism) */
    unsigned char le[4] = {
        (unsigned char)(v & 0xff),
        (unsigned char)((v >> 8) & 0xff),
        (unsigned char)((v >> 16) & 0xff),
        (unsigned char)((v >> 24) & 0xff),
    };
    return buf_put(b, le, 4);
}

static int
buf_put_f64_le(Buf *b, double d)
{
    /* matches struct.pack('<d'): IEEE-754 bits emitted little-endian */
    uint64_t bits;
    memcpy(&bits, &d, 8);
    unsigned char le[8];
    for (int i = 0; i < 8; i++)
        le[i] = (unsigned char)((bits >> (8 * i)) & 0xff);
    return buf_put(b, le, 8);
}

static PyObject *value_to_bytes_py = NULL; /* python fallback */
static PyObject *pointer_type = NULL;      /* api.Pointer, cached */

static int
load_pointer_type(void)
{
    if (pointer_type != NULL)
        return 0;
    PyObject *mod = PyImport_ImportModule("pathway_tpu.internals.api");
    if (mod == NULL)
        return -1;
    pointer_type = PyObject_GetAttrString(mod, "Pointer");
    Py_DECREF(mod);
    return pointer_type == NULL ? -1 : 0;
}

#define SER_MAX_DEPTH 200

static int
serialize_value_d(Buf *b, PyObject *v, int depth)
{
    /* mirrors pathway_tpu.internals.api._value_to_bytes byte-for-byte for
     * the scalar fast paths; exotic values defer to the Python function.
     * Past SER_MAX_DEPTH of tuple nesting the Python fallback takes over
     * (it raises a clean RecursionError instead of blowing the C stack) */
    if (depth > SER_MAX_DEPTH)
        goto python_fallback;
    if (v == Py_None)
        return buf_put(b, "\x00", 1);
    if (PyBool_Check(v)) {
        char t[2] = {'B', v == Py_True ? 1 : 0};
        return buf_put(b, t, 2);
    }
    if (PyFloat_Check(v)) {
        double d = PyFloat_AS_DOUBLE(v);
        if (buf_put(b, "F", 1) < 0)
            return -1;
        return buf_put_f64_le(b, d);
    }
    if (PyUnicode_Check(v)) {
        Py_ssize_t n;
        const char *s = PyUnicode_AsUTF8AndSize(v, &n);
        if (s == NULL)
            return -1;
        if (buf_put(b, "S", 1) < 0)
            return -1;
        return buf_put(b, s, n);
    }
    if (PyBytes_Check(v)) {
        if (buf_put(b, "Y", 1) < 0)
            return -1;
        return buf_put(b, PyBytes_AS_STRING(v), PyBytes_GET_SIZE(v));
    }
    if (PyLong_Check(v)) {
        /* Pointer: "P" + 16-byte LE; other ints: "I" + minimal signed LE
         * of (bit_length + 8)//8 + 1 bytes — both matching api.py */
        if (load_pointer_type() < 0)
            return -1;
        if (PyObject_TypeCheck(v, (PyTypeObject *)pointer_type)) {
            int overflow = 0;
            unsigned char out[17];
            out[0] = 'P';
            /* 128-bit value: low 64 bits via mask, high via shift */
            PyObject *lo64 = NULL, *hi = NULL;
            static PyObject *mask64 = NULL, *sh64 = NULL;
            if (mask64 == NULL) {
                mask64 = PyLong_FromUnsignedLongLong(0xFFFFFFFFFFFFFFFFULL);
                sh64 = PyLong_FromLong(64);
                if (mask64 == NULL || sh64 == NULL)
                    return -1;
            }
            lo64 = PyNumber_And(v, mask64);
            hi = PyNumber_Rshift(v, sh64);
            if (lo64 == NULL || hi == NULL) {
                Py_XDECREF(lo64);
                Py_XDECREF(hi);
                return -1;
            }
            uint64_t lo = PyLong_AsUnsignedLongLong(lo64);
            uint64_t hiv = PyLong_AsUnsignedLongLong(hi);
            Py_DECREF(lo64);
            Py_DECREF(hi);
            if (PyErr_Occurred())
                return -1;
            for (int i = 0; i < 8; i++)
                out[1 + i] = (unsigned char)((lo >> (8 * i)) & 0xff);
            for (int i = 0; i < 8; i++)
                out[9 + i] = (unsigned char)((hiv >> (8 * i)) & 0xff);
            (void)overflow;
            return buf_put(b, out, 17);
        }
        int overflow = 0;
        long long sv = PyLong_AsLongLongAndOverflow(v, &overflow);
        if (!overflow && !(sv == -1 && PyErr_Occurred())) {
            uint64_t uv = sv < 0 ? (uint64_t)0 - (uint64_t)sv : (uint64_t)sv;
            int bl = 0;
            while (bl < 64 && (uv >> bl))
                bl++;
            int nbytes = (bl + 8) / 8 + 1;
            unsigned char out[11];
            out[0] = 'I';
            uint64_t tw = (uint64_t)sv; /* two's complement bits */
            for (int i = 0; i < nbytes; i++)
                out[1 + i] = (unsigned char)(
                    i < 8 ? (tw >> (8 * i)) & 0xff : (sv < 0 ? 0xff : 0x00));
            return buf_put(b, out, 1 + nbytes);
        }
        PyErr_Clear(); /* >64-bit plain int: python fallback below */
    } else if (PyTuple_Check(v)) {
        /* "T" + length-prefixed concat of the parts, recursively */
        Py_ssize_t n = PyTuple_GET_SIZE(v);
        if (buf_put(b, "T", 1) < 0 || buf_put_u32(b, (uint32_t)n) < 0)
            return -1;
        for (Py_ssize_t i = 0; i < n; i++) {
            Py_ssize_t mark = b->len;
            if (buf_put_u32(b, 0) < 0)
                return -1;
            if (serialize_value_d(b, PyTuple_GET_ITEM(v, i), depth + 1) < 0)
                return -1;
            uint32_t plen = (uint32_t)(b->len - mark - 4);
            unsigned char le[4] = {
                (unsigned char)(plen & 0xff),
                (unsigned char)((plen >> 8) & 0xff),
                (unsigned char)((plen >> 16) & 0xff),
                (unsigned char)((plen >> 24) & 0xff),
            };
            memcpy(b->buf + mark, le, 4);
        }
        return 0;
    }
    /* everything else -> python impl */
python_fallback:
    if (value_to_bytes_py == NULL) {
        PyObject *mod = PyImport_ImportModule("pathway_tpu.internals.api");
        if (mod == NULL)
            return -1;
        value_to_bytes_py = PyObject_GetAttrString(mod, "_value_to_bytes");
        Py_DECREF(mod);
        if (value_to_bytes_py == NULL)
            return -1;
    }
    PyObject *bytes = PyObject_CallOneArg(value_to_bytes_py, v);
    if (bytes == NULL)
        return -1;
    int rc = buf_put(b, PyBytes_AS_STRING(bytes), PyBytes_GET_SIZE(bytes));
    Py_DECREF(bytes);
    return rc;
}

static int
serialize_value(Buf *b, PyObject *v)
{
    return serialize_value_d(b, v, 0);
}

static PyObject *
fast_value_bytes(PyObject *self, PyObject *args_tuple)
{
    if (!PyTuple_Check(args_tuple)) {
        PyErr_SetString(PyExc_TypeError, "value_bytes expects a tuple");
        return NULL;
    }
    Py_ssize_t n = PyTuple_GET_SIZE(args_tuple);
    Buf b = {PyMem_Malloc(256), 0, 256};
    if (b.buf == NULL)
        return PyErr_NoMemory();
    if (buf_put_u32(&b, (uint32_t)n) < 0)
        goto fail;
    for (Py_ssize_t i = 0; i < n; i++) {
        /* length-prefix each serialized value (injective concat) */
        Py_ssize_t mark = b.len;
        if (buf_put_u32(&b, 0) < 0)
            goto fail;
        if (serialize_value(&b, PyTuple_GET_ITEM(args_tuple, i)) < 0)
            goto fail;
        uint32_t plen = (uint32_t)(b.len - mark - 4);
        memcpy(b.buf + mark, &plen, 4);
    }
    PyObject *out = PyBytes_FromStringAndSize(b.buf, b.len);
    PyMem_Free(b.buf);
    return out;
fail:
    PyMem_Free(b.buf);
    return NULL;
}

/* blake2b-128: shared single implementation (native/pw_blake2b.h) —
 * digests identical to hashlib.blake2b(data, digest_size=16) so natively
 * minted Pointers equal the Python path's (persistence + multi-process
 * determinism; one copy shared with exec.cpp so the fused join's pair
 * keys can never drift from ref_scalar). */
#include "pw_blake2b.h"

static PyObject *
one_long(void)
{
    static PyObject *one = NULL;
    if (one == NULL)
        one = PyLong_FromLong(1);
    return one;
}

/* -- batch-plane helpers -------------------------------------------------
 * One C call per delta batch instead of a Python loop per delta: these are
 * the per-row list/tuple plumbing of every relational node (split deltas
 * into columns, project row columns, re-zip computed rows, filter by mask,
 * parse connector upserts, deliver sorted output callbacks). The reference
 * keeps the same loops inside Rust operators (dataflow.rs); here they are
 * the C substrate under engine/nodes.py. */

/* split_deltas(deltas) -> (keys, rows, diffs) */
static PyObject *
fast_split_deltas(PyObject *self, PyObject *arg)
{
    PyObject *seq = PySequence_Fast(arg, "split_deltas expects a sequence");
    if (seq == NULL)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject *keys = PyList_New(n);
    PyObject *rows = PyList_New(n);
    PyObject *diffs = PyList_New(n);
    if (keys == NULL || rows == NULL || diffs == NULL)
        goto fail;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *d = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(d) || PyTuple_GET_SIZE(d) != 3) {
            PyErr_SetString(PyExc_TypeError, "delta must be (key, row, diff)");
            goto fail;
        }
        PyObject *k = PyTuple_GET_ITEM(d, 0);
        PyObject *r = PyTuple_GET_ITEM(d, 1);
        PyObject *df = PyTuple_GET_ITEM(d, 2);
        Py_INCREF(k);
        PyList_SET_ITEM(keys, i, k);
        Py_INCREF(r);
        PyList_SET_ITEM(rows, i, r);
        Py_INCREF(df);
        PyList_SET_ITEM(diffs, i, df);
    }
    Py_DECREF(seq);
    PyObject *out = PyTuple_Pack(3, keys, rows, diffs);
    Py_DECREF(keys);
    Py_DECREF(rows);
    Py_DECREF(diffs);
    return out;
fail:
    Py_XDECREF(keys);
    Py_XDECREF(rows);
    Py_XDECREF(diffs);
    Py_DECREF(seq);
    return NULL;
}

/* project_col(rows, j) -> [row[j] for row in rows] */
static PyObject *
fast_project_col(PyObject *self, PyObject *args)
{
    PyObject *rows;
    Py_ssize_t j;
    if (!PyArg_ParseTuple(args, "On", &rows, &j))
        return NULL;
    PyObject *seq = PySequence_Fast(rows, "project_col expects a sequence");
    if (seq == NULL)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject *out = PyList_New(n);
    if (out == NULL) {
        Py_DECREF(seq);
        return NULL;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *r = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(r) || j < 0 || j >= PyTuple_GET_SIZE(r)) {
            Py_DECREF(out);
            Py_DECREF(seq);
            PyErr_SetString(PyExc_IndexError,
                            "project_col: row is not a tuple of that width");
            return NULL;
        }
        PyObject *v = PyTuple_GET_ITEM(r, j);
        Py_INCREF(v);
        PyList_SET_ITEM(out, i, v);
    }
    Py_DECREF(seq);
    return out;
}

/* project_tuples(rows, idx_tuple) -> [tuple(row[j] for j in idx) ...] */
static PyObject *
fast_project_tuples(PyObject *self, PyObject *args)
{
    PyObject *rows, *idx;
    if (!PyArg_ParseTuple(args, "OO!", &rows, &PyTuple_Type, &idx))
        return NULL;
    Py_ssize_t m = PyTuple_GET_SIZE(idx);
    Py_ssize_t js[32];
    if (m > 32) {
        PyErr_SetString(PyExc_ValueError, "project_tuples: too many columns");
        return NULL;
    }
    for (Py_ssize_t t = 0; t < m; t++) {
        js[t] = PyLong_AsSsize_t(PyTuple_GET_ITEM(idx, t));
        if (js[t] == -1 && PyErr_Occurred())
            return NULL;
    }
    PyObject *seq = PySequence_Fast(rows, "project_tuples expects a sequence");
    if (seq == NULL)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject *out = PyList_New(n);
    if (out == NULL) {
        Py_DECREF(seq);
        return NULL;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *r = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(r)) {
            PyErr_SetString(PyExc_TypeError, "project_tuples: row not tuple");
            goto fail;
        }
        Py_ssize_t w = PyTuple_GET_SIZE(r);
        PyObject *tup = PyTuple_New(m);
        if (tup == NULL)
            goto fail;
        for (Py_ssize_t t = 0; t < m; t++) {
            if (js[t] < 0 || js[t] >= w) {
                Py_DECREF(tup);
                PyErr_SetString(PyExc_IndexError, "project_tuples: bad index");
                goto fail;
            }
            PyObject *v = PyTuple_GET_ITEM(r, js[t]);
            Py_INCREF(v);
            PyTuple_SET_ITEM(tup, t, v);
        }
        PyList_SET_ITEM(out, i, tup);
    }
    Py_DECREF(seq);
    return out;
fail:
    Py_DECREF(out);
    Py_DECREF(seq);
    return NULL;
}

/* rezip(deltas, new_rows) -> [(k, new_row, d), ...] */
static PyObject *
fast_rezip(PyObject *self, PyObject *args)
{
    PyObject *deltas, *new_rows;
    if (!PyArg_ParseTuple(args, "OO", &deltas, &new_rows))
        return NULL;
    PyObject *dseq = PySequence_Fast(deltas, "rezip expects sequences");
    if (dseq == NULL)
        return NULL;
    PyObject *rseq = PySequence_Fast(new_rows, "rezip expects sequences");
    if (rseq == NULL) {
        Py_DECREF(dseq);
        return NULL;
    }
    Py_ssize_t n = PySequence_Fast_GET_SIZE(dseq);
    if (PySequence_Fast_GET_SIZE(rseq) != n) {
        PyErr_SetString(PyExc_ValueError, "rezip: length mismatch");
        Py_DECREF(dseq);
        Py_DECREF(rseq);
        return NULL;
    }
    PyObject *out = PyList_New(n);
    if (out == NULL) {
        Py_DECREF(dseq);
        Py_DECREF(rseq);
        return NULL;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *d = PySequence_Fast_GET_ITEM(dseq, i);
        if (!PyTuple_Check(d) || PyTuple_GET_SIZE(d) != 3) {
            PyErr_SetString(PyExc_TypeError, "delta must be (key, row, diff)");
            Py_DECREF(out);
            Py_DECREF(dseq);
            Py_DECREF(rseq);
            return NULL;
        }
        PyObject *t = PyTuple_Pack(3, PyTuple_GET_ITEM(d, 0),
                                   PySequence_Fast_GET_ITEM(rseq, i),
                                   PyTuple_GET_ITEM(d, 2));
        if (t == NULL) {
            Py_DECREF(out);
            Py_DECREF(dseq);
            Py_DECREF(rseq);
            return NULL;
        }
        PyList_SET_ITEM(out, i, t);
    }
    Py_DECREF(dseq);
    Py_DECREF(rseq);
    return out;
}

/* filter_deltas(deltas, mask) -> [d for d, m in zip(deltas, mask)
 *                                 if m is True]
 * Matches engine filter semantics for exact-bool masks: True keeps the
 * row, False drops it. Any non-bool entry (None, Error, np.bool_) raises
 * TypeError so the Python caller falls back to its general loop — the C
 * path never guesses at truthiness. */
static PyObject *
fast_filter_deltas(PyObject *self, PyObject *args)
{
    PyObject *deltas, *mask;
    if (!PyArg_ParseTuple(args, "OO", &deltas, &mask))
        return NULL;
    PyObject *dseq = PySequence_Fast(deltas, "filter_deltas expects sequences");
    if (dseq == NULL)
        return NULL;
    PyObject *mseq = PySequence_Fast(mask, "filter_deltas expects sequences");
    if (mseq == NULL) {
        Py_DECREF(dseq);
        return NULL;
    }
    Py_ssize_t n = PySequence_Fast_GET_SIZE(dseq);
    if (PySequence_Fast_GET_SIZE(mseq) != n) {
        PyErr_SetString(PyExc_ValueError, "filter_deltas: length mismatch");
        Py_DECREF(dseq);
        Py_DECREF(mseq);
        return NULL;
    }
    PyObject *out = PyList_New(0);
    if (out == NULL) {
        Py_DECREF(dseq);
        Py_DECREF(mseq);
        return NULL;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *m = PySequence_Fast_GET_ITEM(mseq, i);
        if (m == Py_True) {
            if (PyList_Append(out, PySequence_Fast_GET_ITEM(dseq, i)) < 0) {
                Py_DECREF(out);
                Py_DECREF(dseq);
                Py_DECREF(mseq);
                return NULL;
            }
        } else if (m != Py_False) {
            PyErr_SetString(PyExc_TypeError,
                            "filter_deltas: non-bool mask entry");
            Py_DECREF(out);
            Py_DECREF(dseq);
            Py_DECREF(mseq);
            return NULL;
        }
    }
    Py_DECREF(dseq);
    Py_DECREF(mseq);
    return out;
}

/* parse_upserts(msgs, start, cols, defaults, key_base, seq0, mask, ptr_type)
 *   msgs: list whose entries from `start` on are kwargs dicts of simple
 *   upserts (the caller segregates other message kinds). Builds one
 *   (Pointer(key_base+seq & mask), row_tuple, 1) per dict.
 *   Returns (deltas_list, new_seq). */
static PyObject *
fast_parse_upserts(PyObject *self, PyObject *args)
{
    PyObject *msgs, *cols, *defaults, *key_base_obj, *mask_obj, *ptr_type;
    Py_ssize_t start;
    long long seq0;
    if (!PyArg_ParseTuple(args, "OnO!O!OLOO", &msgs, &start, &PyTuple_Type,
                          &cols, &PyTuple_Type, &defaults, &key_base_obj,
                          &seq0, &mask_obj, &ptr_type))
        return NULL;
    PyObject *seq = PySequence_Fast(msgs, "parse_upserts expects a sequence");
    if (seq == NULL)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    Py_ssize_t w = PyTuple_GET_SIZE(cols);
    if (PyTuple_GET_SIZE(defaults) != w) {
        PyErr_SetString(PyExc_ValueError, "parse_upserts: defaults width");
        Py_DECREF(seq);
        return NULL;
    }
    PyObject *out = PyList_New(n - start);
    if (out == NULL) {
        Py_DECREF(seq);
        return NULL;
    }
    PyObject *one = PyLong_FromLong(1);
    long long sq = seq0;
    for (Py_ssize_t i = start; i < n; i++) {
        PyObject *values = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyDict_Check(values)) {
            PyErr_SetString(PyExc_TypeError, "parse_upserts: msg not a dict");
            goto fail;
        }
        PyObject *row = PyTuple_New(w);
        if (row == NULL)
            goto fail;
        for (Py_ssize_t c = 0; c < w; c++) {
            PyObject *v = PyDict_GetItemWithError(
                values, PyTuple_GET_ITEM(cols, c));
            if (v == NULL) {
                if (PyErr_Occurred()) {
                    Py_DECREF(row);
                    goto fail;
                }
                v = PyTuple_GET_ITEM(defaults, c);
            }
            Py_INCREF(v);
            PyTuple_SET_ITEM(row, c, v);
        }
        sq += 1;
        /* key = ptr_type((key_base + sq) & mask) — arbitrary-precision
         * arithmetic through the Python API: key_base is a 128-bit int */
        PyObject *sq_obj = PyLong_FromLongLong(sq);
        if (sq_obj == NULL) {
            Py_DECREF(row);
            goto fail;
        }
        PyObject *raw = PyNumber_Add(key_base_obj, sq_obj);
        Py_DECREF(sq_obj);
        PyObject *masked = raw ? PyNumber_And(raw, mask_obj) : NULL;
        Py_XDECREF(raw);
        PyObject *key = masked ? PyObject_CallOneArg(ptr_type, masked) : NULL;
        Py_XDECREF(masked);
        if (key == NULL) {
            Py_DECREF(row);
            goto fail;
        }
        PyObject *t = PyTuple_New(3);
        if (t == NULL) {
            Py_DECREF(key);
            Py_DECREF(row);
            goto fail;
        }
        PyTuple_SET_ITEM(t, 0, key);
        PyTuple_SET_ITEM(t, 1, row);
        Py_INCREF(one);
        PyTuple_SET_ITEM(t, 2, one);
        PyList_SET_ITEM(out, i - start, t);
    }
    Py_DECREF(one);
    Py_DECREF(seq);
    PyObject *res = Py_BuildValue("(OL)", out, sq);
    Py_DECREF(out);
    return res;
fail:
    Py_DECREF(one);
    Py_DECREF(out);
    Py_DECREF(seq);
    return NULL;
}

/* deliver(deltas, time, cb, cols_or_None)
 * Stable partition of a consolidated batch — all retractions first, then
 * all insertions, each preserving producer order (which is deterministic:
 * node outputs are insertion-ordered dicts). Retract-before-insert is the
 * contract upsert sinks need; producer order within each class keeps the
 * callback sequence reproducible without a full (diff, key) sort on the
 * hot path. Calls cb per delta:
 *   cols is None:  cb(key, row, time, diff)
 *   cols a tuple:  cb(key, {col: val}, time, diff > 0)   (pw.io.subscribe)
 */
static int
deliver_one(PyObject *d, PyObject *time_obj, PyObject *cb, PyObject *cols,
            int want_dict)
{
    PyObject *key = PyTuple_GET_ITEM(d, 0);
    PyObject *row = PyTuple_GET_ITEM(d, 1);
    PyObject *diff = PyTuple_GET_ITEM(d, 2);
    PyObject *payload;
    PyObject *diff_arg;
    if (want_dict) {
        if (!PyTuple_Check(row) ||
            PyTuple_GET_SIZE(row) != PyTuple_GET_SIZE(cols)) {
            PyErr_SetString(PyExc_ValueError, "deliver: row width");
            return -1;
        }
        payload = PyDict_New();
        if (payload == NULL)
            return -1;
        for (Py_ssize_t c = 0; c < PyTuple_GET_SIZE(cols); c++) {
            if (PyDict_SetItem(payload, PyTuple_GET_ITEM(cols, c),
                               PyTuple_GET_ITEM(row, c)) < 0) {
                Py_DECREF(payload);
                return -1;
            }
        }
        int pos = PyObject_RichCompareBool(diff, one_long(), Py_GE);
        if (pos < 0) {
            Py_DECREF(payload);
            return -1;
        }
        diff_arg = pos ? Py_True : Py_False;
    } else {
        payload = row;
        Py_INCREF(payload);
        diff_arg = diff;
    }
    /* vectorcall: the per-output-delta dispatch into user callbacks is
     * the subscribe hot loop — skip the ObjArgs tuple pack */
    PyObject *stack[4] = {key, payload, time_obj, diff_arg};
    PyObject *r = PyObject_Vectorcall(cb, stack, 4, NULL);
    Py_DECREF(payload);
    if (r == NULL)
        return -1;
    Py_DECREF(r);
    return 0;
}

static PyObject *
fast_deliver(PyObject *self, PyObject *args)
{
    PyObject *deltas, *time_obj, *cb, *cols;
    if (!PyArg_ParseTuple(args, "OOOO", &deltas, &time_obj, &cb, &cols))
        return NULL;
    int want_dict = cols != Py_None;
    if (want_dict && !PyTuple_Check(cols)) {
        PyErr_SetString(PyExc_TypeError, "deliver: cols must be tuple|None");
        return NULL;
    }
    PyObject *seq = PySequence_Fast(deltas, "deliver expects a sequence");
    if (seq == NULL)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *d = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(d) || PyTuple_GET_SIZE(d) != 3) {
            PyErr_SetString(PyExc_TypeError, "delta must be (key, row, diff)");
            Py_DECREF(seq);
            return NULL;
        }
        long long df = PyLong_AsLongLong(PyTuple_GET_ITEM(d, 2));
        if (df == -1 && PyErr_Occurred()) {
            Py_DECREF(seq);
            return NULL;
        }
        if (df < 0 && deliver_one(d, time_obj, cb, cols, want_dict) < 0) {
            Py_DECREF(seq);
            return NULL;
        }
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *d = PySequence_Fast_GET_ITEM(seq, i);
        long long df = PyLong_AsLongLong(PyTuple_GET_ITEM(d, 2));
        if (df >= 0 && deliver_one(d, time_obj, cb, cols, want_dict) < 0) {
            Py_DECREF(seq);
            return NULL;
        }
    }
    Py_DECREF(seq);
    Py_RETURN_NONE;
}

/* ref_scalar(args_tuple) -> Pointer
 * Full native key mint: injective serialization (value_bytes) + blake2b-128
 * + Pointer construction. Byte-identical to api.ref_scalar. */
static PyObject *mint_key_from_tuple(PyObject *args_tuple);

static PyObject *
fast_ref_scalar(PyObject *self, PyObject *args_tuple)
{
    if (!PyTuple_Check(args_tuple)) {
        PyErr_SetString(PyExc_TypeError, "ref_scalar expects a tuple");
        return NULL;
    }
    return mint_key_from_tuple(args_tuple);
}

/* variadic spelling — drop-in for api.ref_scalar(*args) so hot callers
 * (the join executor's per-output-pair key mint) can invoke the builtin
 * directly with no Python wrapper frame */
static PyObject *
fast_ref_scalar_v(PyObject *self, PyObject *args)
{
    return mint_key_from_tuple(args);
}

static PyObject *
mint_key_from_tuple(PyObject *args_tuple)
{
    if (load_pointer_type() < 0)
        return NULL;
    Py_ssize_t n = PyTuple_GET_SIZE(args_tuple);
    Buf b = {PyMem_Malloc(256), 0, 256};
    if (b.buf == NULL)
        return PyErr_NoMemory();
    if (buf_put_u32(&b, (uint32_t)n) < 0)
        goto fail;
    for (Py_ssize_t i = 0; i < n; i++) {
        Py_ssize_t mark = b.len;
        if (buf_put_u32(&b, 0) < 0)
            goto fail;
        if (serialize_value(&b, PyTuple_GET_ITEM(args_tuple, i)) < 0)
            goto fail;
        uint32_t plen = (uint32_t)(b.len - mark - 4);
        unsigned char le[4] = {
            (unsigned char)(plen & 0xff),
            (unsigned char)((plen >> 8) & 0xff),
            (unsigned char)((plen >> 16) & 0xff),
            (unsigned char)((plen >> 24) & 0xff),
        };
        memcpy(b.buf + mark, le, 4);
    }
    unsigned char digest[16];
    pw_b2b_digest16(digest, (const unsigned char *)b.buf, (size_t)b.len);
    PyMem_Free(b.buf);
    b.buf = NULL;
    uint64_t lo = 0, hi = 0;
    for (int i = 7; i >= 0; i--)
        lo = (lo << 8) | digest[i];
    for (int i = 15; i >= 8; i--)
        hi = (hi << 8) | digest[i];
    PyObject *lo_o = PyLong_FromUnsignedLongLong(lo);
    PyObject *hi_o = PyLong_FromUnsignedLongLong(hi);
    static PyObject *sh64 = NULL;
    if (sh64 == NULL)
        sh64 = PyLong_FromLong(64);
    PyObject *shifted =
        (lo_o && hi_o && sh64) ? PyNumber_Lshift(hi_o, sh64) : NULL;
    PyObject *full = shifted ? PyNumber_Or(shifted, lo_o) : NULL;
    Py_XDECREF(lo_o);
    Py_XDECREF(hi_o);
    Py_XDECREF(shifted);
    if (full == NULL)
        return NULL;
    PyObject *key = PyObject_CallOneArg(pointer_type, full);
    Py_DECREF(full);
    return key;
fail:
    PyMem_Free(b.buf);
    return NULL;
}

/* parse_pk_upserts(dicts, cols, defaults, pkeys, live_rows) -> deltas
 * Primary-keyed upsert sessions in one C pass (the CDC/connector hot
 * path): per row dict, build the row tuple, mint the key from the pk
 * VALUES (native blake2b — byte-identical to api.ref_scalar), retract
 * the previous live row for that key, install the new one. live_rows is
 * the parser's own session dict, shared with the per-message Python
 * path so mixed batches stay consistent. A pk missing from a dict
 * raises KeyError exactly like the Python path's values[c]. */
static PyObject *
fast_parse_pk_upserts(PyObject *self, PyObject *args)
{
    PyObject *dicts, *cols, *defaults, *pkeys, *live_rows;
    if (!PyArg_ParseTuple(args, "OO!O!O!O!", &dicts, &PyTuple_Type, &cols,
                          &PyTuple_Type, &defaults, &PyTuple_Type, &pkeys,
                          &PyDict_Type, &live_rows))
        return NULL;
    PyObject *seq = PySequence_Fast(dicts, "parse_pk_upserts: sequence");
    if (seq == NULL)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    Py_ssize_t w = PyTuple_GET_SIZE(cols);
    Py_ssize_t npk = PyTuple_GET_SIZE(pkeys);
    if (PyTuple_GET_SIZE(defaults) != w) {
        PyErr_SetString(PyExc_ValueError, "parse_pk_upserts: widths");
        Py_DECREF(seq);
        return NULL;
    }
    PyObject *out = PyList_New(0);
    if (out == NULL) {
        Py_DECREF(seq);
        return NULL;
    }
    PyObject *one = PyLong_FromLong(1);
    PyObject *neg = PyLong_FromLong(-1);
    PyObject *pkvals = PyTuple_New(npk);
    if (one == NULL || neg == NULL || pkvals == NULL)
        goto fail;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *values = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyDict_Check(values)) {
            PyErr_SetString(PyExc_TypeError,
                            "parse_pk_upserts: msg not a dict");
            goto fail;
        }
        PyObject *row = PyTuple_New(w);
        if (row == NULL)
            goto fail;
        for (Py_ssize_t c = 0; c < w; c++) {
            PyObject *v = PyDict_GetItemWithError(
                values, PyTuple_GET_ITEM(cols, c));
            if (v == NULL) {
                if (PyErr_Occurred()) {
                    Py_DECREF(row);
                    goto fail;
                }
                v = PyTuple_GET_ITEM(defaults, c);
            }
            Py_INCREF(v);
            PyTuple_SET_ITEM(row, c, v);
        }
        for (Py_ssize_t p = 0; p < npk; p++) {
            PyObject *v = PyDict_GetItemWithError(
                values, PyTuple_GET_ITEM(pkeys, p));
            if (v == NULL) {
                if (!PyErr_Occurred())
                    PyErr_SetObject(PyExc_KeyError,
                                    PyTuple_GET_ITEM(pkeys, p));
                Py_DECREF(row);
                goto fail;
            }
            Py_INCREF(v);
            /* pkvals slots are overwritten per row; SET_ITEM drops the
             * previous ref */
            PyObject *old = PyTuple_GET_ITEM(pkvals, p);
            PyTuple_SET_ITEM(pkvals, p, v);
            Py_XDECREF(old);
        }
        PyObject *key = mint_key_from_tuple(pkvals);
        if (key == NULL) {
            Py_DECREF(row);
            goto fail;
        }
        PyObject *prev = PyDict_GetItemWithError(live_rows, key);
        if (prev == NULL && PyErr_Occurred()) {
            Py_DECREF(key);
            Py_DECREF(row);
            goto fail;
        }
        if (prev != NULL) {
            PyObject *t = PyTuple_Pack(3, key, prev, neg);
            if (t == NULL || PyList_Append(out, t) < 0) {
                Py_XDECREF(t);
                Py_DECREF(key);
                Py_DECREF(row);
                goto fail;
            }
            Py_DECREF(t);
        }
        if (PyDict_SetItem(live_rows, key, row) < 0) {
            Py_DECREF(key);
            Py_DECREF(row);
            goto fail;
        }
        PyObject *t = PyTuple_Pack(3, key, row, one);
        Py_DECREF(key);
        Py_DECREF(row);
        if (t == NULL || PyList_Append(out, t) < 0) {
            Py_XDECREF(t);
            goto fail;
        }
        Py_DECREF(t);
    }
    Py_DECREF(one);
    Py_DECREF(neg);
    Py_DECREF(pkvals);
    Py_DECREF(seq);
    return out;
fail:
    Py_XDECREF(one);
    Py_XDECREF(neg);
    Py_XDECREF(pkvals);
    Py_DECREF(out);
    Py_DECREF(seq);
    return NULL;
}

/* -- binop(left, right, code, error_obj, op) -----------------------------
 * Column-wise binary operator: the expression plane's hot loop. Numeric
 * elements (bool/int64/float) compute in C with EXACT Python semantics
 * (floor division/modulo sign rules, int/float promotion, overflow to
 * Python bigints via per-element fallback). Every non-fast element —
 * strings, None, big ints, division by zero — falls back to calling the
 * REAL Python operator on that element, so behavior (including the
 * exception messages the error log records) is identical to the Python
 * loop by construction. ERROR in either operand is absorbing.
 *
 * Returns (out_list, errs) where errs is [(i, message), ...] for
 * elements whose operator raised (their out slot is error_obj).
 *
 * Op codes: 0:+ 1:- 2:* 3:/ 4:// 5:% 6:< 7:<= 8:> 9:>= 10:== 11:!=
 *           12:& 13:| 14:^
 */

enum { B_ADD, B_SUB, B_MUL, B_DIV, B_FDIV, B_MOD, B_LT, B_LE, B_GT,
       B_GE, B_EQ, B_NE, B_AND, B_OR, B_XOR };

/* tagged numeric view of a cell: 0=not numeric, 1=int(i64), 2=float,
 * 3=bool (int value in i) */
static inline int
num_view(PyObject *v, int64_t *i, double *f)
{
    /* CheckExact: int/float SUBCLASSES (np.float64, user types with
     * overridden operators) must take the python fallback so their
     * overrides and result types are honored */
    if (v == Py_True) { *i = 1; return 3; }
    if (v == Py_False) { *i = 0; return 3; }
    if (PyFloat_CheckExact(v)) { *f = PyFloat_AS_DOUBLE(v); return 2; }
    if (PyLong_CheckExact(v)) {
        int ovf = 0;
        *i = PyLong_AsLongLongAndOverflow(v, &ovf);
        if (ovf)
            return 0; /* bigint: python fallback */
        return 1;
    }
    return 0;
}

static PyObject *
fast_binop(PyObject *self, PyObject *args)
{
    PyObject *left, *right, *error_obj, *op;
    int code;
    if (!PyArg_ParseTuple(args, "OOiOO", &left, &right, &code, &error_obj,
                          &op))
        return NULL;
    if (!PyList_Check(left) || !PyList_Check(right) ||
        PyList_GET_SIZE(left) != PyList_GET_SIZE(right)) {
        PyErr_SetString(PyExc_TypeError, "binop expects two equal lists");
        return NULL;
    }
    Py_ssize_t n = PyList_GET_SIZE(left);
    PyObject *out = PyList_New(n);
    if (out == NULL)
        return NULL;
    PyObject *errs = PyList_New(0);
    if (errs == NULL) {
        Py_DECREF(out);
        return NULL;
    }
    for (Py_ssize_t idx = 0; idx < n; idx++) {
        PyObject *a = PyList_GET_ITEM(left, idx);
        PyObject *b = PyList_GET_ITEM(right, idx);
        if (a == error_obj || b == error_obj) {
            Py_INCREF(error_obj);
            PyList_SET_ITEM(out, idx, error_obj);
            continue;
        }
        int64_t ai = 0, bi = 0;
        double af = 0.0, bf = 0.0;
        int ta = num_view(a, &ai, &af);
        int tb = num_view(b, &bi, &bf);
        PyObject *r = NULL;
        if (ta != 0 && tb != 0) {
            const int both_int = ta != 2 && tb != 2;
            if (code >= B_LT && code <= B_NE) {
                /* comparisons: exact across int/float via long double
                 * (x86-64: 64-bit mantissa covers every int64) */
                long double x = ta == 2 ? (long double)af : (long double)ai;
                long double y = tb == 2 ? (long double)bf : (long double)bi;
                int cres =
                    code == B_LT   ? x < y
                    : code == B_LE ? x <= y
                    : code == B_GT ? x > y
                    : code == B_GE ? x >= y
                    : code == B_EQ ? x == y
                                   : x != y;
                r = cres ? Py_True : Py_False;
                Py_INCREF(r);
            } else if (code >= B_AND && code <= B_XOR) {
                if (ta == 3 && tb == 3) {
                    int cres = code == B_AND   ? (ai && bi)
                               : code == B_OR  ? (ai || bi)
                                               : (ai != bi);
                    r = cres ? Py_True : Py_False;
                    Py_INCREF(r);
                } else if (both_int) {
                    int64_t cres = code == B_AND  ? (ai & bi)
                                   : code == B_OR ? (ai | bi)
                                                  : (ai ^ bi);
                    r = PyLong_FromLongLong(cres);
                } /* float operand: python fallback (TypeError) */
            } else if (both_int) {
                int64_t cres = 0;
                int ok = 1;
                switch (code) {
                case B_ADD:
                    ok = !__builtin_add_overflow(ai, bi, &cres);
                    break;
                case B_SUB:
                    ok = !__builtin_sub_overflow(ai, bi, &cres);
                    break;
                case B_MUL:
                    ok = !__builtin_mul_overflow(ai, bi, &cres);
                    break;
                case B_DIV:
                    /* (double)a/(double)b is correctly rounded only when
                     * both operands are exact in double; CPython's
                     * long_true_divide is correctly rounded for ANY ints,
                     * so larger operands take the fallback (1-ulp parity,
                     * review r4) */
                    if (bi == 0 || ai > (int64_t)1 << 53 ||
                        ai < -((int64_t)1 << 53) ||
                        bi > (int64_t)1 << 53 || bi < -((int64_t)1 << 53))
                        ok = 0;
                    else
                        r = PyFloat_FromDouble((double)ai / (double)bi);
                    break;
                case B_FDIV:
                    if (bi == 0 || (ai == INT64_MIN && bi == -1)) {
                        ok = 0;
                    } else {
                        /* Python floor semantics for negatives */
                        cres = ai / bi;
                        if ((ai % bi != 0) && ((ai < 0) != (bi < 0)))
                            cres -= 1;
                    }
                    break;
                case B_MOD:
                    if (bi == 0 || (ai == INT64_MIN && bi == -1)) {
                        ok = 0;
                    } else {
                        /* result sign follows the divisor */
                        cres = ai % bi;
                        if (cres != 0 && ((cres < 0) != (bi < 0)))
                            cres += bi;
                    }
                    break;
                }
                if (r == NULL && ok)
                    r = PyLong_FromLongLong(cres);
                else if (!ok)
                    r = NULL; /* overflow / div-zero: python fallback */
            } else {
                /* at least one float: promote */
                double x = ta == 2 ? af : (double)ai;
                double y = tb == 2 ? bf : (double)bi;
                switch (code) {
                case B_ADD:
                    r = PyFloat_FromDouble(x + y);
                    break;
                case B_SUB:
                    r = PyFloat_FromDouble(x - y);
                    break;
                case B_MUL:
                    r = PyFloat_FromDouble(x * y);
                    break;
                case B_DIV:
                    if (y != 0.0)
                        r = PyFloat_FromDouble(x / y);
                    break; /* /0.0 raises in Python: fallback */
                case B_FDIV:
                    /* CPython float floor-division is fmod-based, not
                     * floor(x/y) — underflow/rounding-boundary cases
                     * diverge (review r4): mirror float_divmod exactly,
                     * including the half-way correction */
                    if (y != 0.0) {
                        double m = fmod(x, y);
                        double d = (x - m) / y;
                        if (m != 0.0) {
                            if ((y < 0.0) != (m < 0.0))
                                d -= 1.0;
                        }
                        if (d != 0.0) {
                            double fd = floor(d);
                            if (d - fd > 0.5)
                                fd += 1.0;
                            d = fd;
                        } else {
                            d = copysign(0.0, x / y);
                        }
                        r = PyFloat_FromDouble(d);
                    }
                    break;
                case B_MOD:
                    if (y != 0.0) {
                        /* CPython float_rem: zero results take the
                         * divisor's sign (fmod's -0.0 diverges) */
                        double m = fmod(x, y);
                        if (m != 0.0) {
                            if ((y < 0.0) != (m < 0.0))
                                m += y;
                        } else {
                            m = copysign(0.0, y);
                        }
                        r = PyFloat_FromDouble(m);
                    }
                    break;
                }
            }
        }
        if (r == NULL && !PyErr_Occurred()) {
            /* python fallback: the REAL operator on this element —
             * strings, None, bigints, div-by-zero all behave (and
             * raise) exactly like the interpreted loop */
            r = PyObject_CallFunctionObjArgs(op, a, b, NULL);
            if (r == NULL) {
                /* BaseExceptions (KeyboardInterrupt, SystemExit) must
                 * abort the run, not become ERROR cells */
                if (!PyErr_ExceptionMatches(PyExc_Exception)) {
                    Py_DECREF(out);
                    Py_DECREF(errs);
                    return NULL;
                }
                PyObject *etype, *evalue, *etb;
                PyErr_Fetch(&etype, &evalue, &etb);
                PyObject *msg =
                    evalue ? PyObject_Str(evalue) : PyUnicode_FromString("");
                Py_XDECREF(etype);
                Py_XDECREF(evalue);
                Py_XDECREF(etb);
                if (msg == NULL) {
                    Py_DECREF(out);
                    Py_DECREF(errs);
                    return NULL;
                }
                PyObject *pair = Py_BuildValue("(nN)", idx, msg);
                if (pair == NULL || PyList_Append(errs, pair) < 0) {
                    Py_XDECREF(pair);
                    Py_DECREF(out);
                    Py_DECREF(errs);
                    return NULL;
                }
                Py_DECREF(pair);
                Py_INCREF(error_obj);
                r = error_obj;
            }
        }
        if (r == NULL) {
            Py_DECREF(out);
            Py_DECREF(errs);
            return NULL;
        }
        PyList_SET_ITEM(out, idx, r);
    }
    return Py_BuildValue("(NN)", out, errs);
}

/* module def ------------------------------------------------------------ */

/* capture_apply(rows_dict, updates_list, deltas, time)
 * One C pass over a capture sink's batch: TableState.apply semantics
 * (upserts arriving as (del, add) in any in-batch order land on the
 * added row) plus the (key, row, time, diff) update-history append.
 * The capture sink sees EVERY output row of a pipeline — at join
 * fanouts this loop is a top-3 cost of the whole run. */
static PyObject *
fast_capture_apply(PyObject *self, PyObject *args)
{
    PyObject *rows, *updates, *deltas, *time_obj;
    if (!PyArg_ParseTuple(args, "O!O!OO", &PyDict_Type, &rows,
                          &PyList_Type, &updates, &deltas, &time_obj))
        return NULL;
    PyObject *seq = PySequence_Fast(deltas, "capture_apply: sequence");
    if (seq == NULL)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    PyObject *pending = NULL; /* key -> row for in-batch upserts */
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *d = PySequence_Fast_GET_ITEM(seq, i);
        if (!PyTuple_Check(d) || PyTuple_GET_SIZE(d) != 3) {
            PyErr_SetString(PyExc_TypeError,
                            "capture_apply: delta must be (key, row, diff)");
            goto fail;
        }
        PyObject *key = PyTuple_GET_ITEM(d, 0);
        PyObject *row = PyTuple_GET_ITEM(d, 1);
        PyObject *diff = PyTuple_GET_ITEM(d, 2);
        long long df = PyLong_AsLongLong(diff);
        if (df == -1 && PyErr_Occurred())
            goto fail;
        /* update history entry */
        PyObject *u = PyTuple_New(4);
        if (u == NULL)
            goto fail;
        Py_INCREF(key);
        PyTuple_SET_ITEM(u, 0, key);
        Py_INCREF(row);
        PyTuple_SET_ITEM(u, 1, row);
        Py_INCREF(time_obj);
        PyTuple_SET_ITEM(u, 2, time_obj);
        Py_INCREF(diff);
        PyTuple_SET_ITEM(u, 3, diff);
        if (PyList_Append(updates, u) < 0) {
            Py_DECREF(u);
            goto fail;
        }
        Py_DECREF(u);
        /* table state */
        if (df > 0) {
            int have = PyDict_Contains(rows, key);
            if (have < 0)
                goto fail;
            int pend = pending != NULL && PyDict_Contains(pending, key);
            if (pend < 0)
                goto fail;
            if (have && !pend) {
                if (pending == NULL) {
                    pending = PyDict_New();
                    if (pending == NULL)
                        goto fail;
                }
                if (PyDict_SetItem(pending, key, row) < 0)
                    goto fail;
            } else if (PyDict_SetItem(rows, key, row) < 0) {
                goto fail;
            }
        } else if (df < 0) {
            int have = PyDict_Contains(rows, key);
            if (have < 0)
                goto fail;
            if (have && PyDict_DelItem(rows, key) < 0)
                goto fail;
        }
    }
    if (pending != NULL) {
        PyObject *key, *row;
        Py_ssize_t pos = 0;
        while (PyDict_Next(pending, &pos, &key, &row))
            if (PyDict_SetItem(rows, key, row) < 0)
                goto fail;
        Py_DECREF(pending);
    }
    Py_DECREF(seq);
    Py_RETURN_NONE;
fail:
    Py_XDECREF(pending);
    Py_DECREF(seq);
    return NULL;
}

static PyMethodDef methods[] = {
    {"consolidate", fast_consolidate, METH_O,
     "Sum multiplicities of identical (key,row) pairs, drop zeros."},
    {"freeze_rows", fast_freeze_rows, METH_O,
     "Hashable stand-ins for a batch of rows."},
    {"value_bytes", fast_value_bytes, METH_O,
     "Injective length-prefixed serialization of a value tuple."},
    {"split_deltas", fast_split_deltas, METH_O,
     "split_deltas(deltas) -> (keys, rows, diffs)"},
    {"project_col", fast_project_col, METH_VARARGS,
     "project_col(rows, j) -> [row[j] for row in rows]"},
    {"project_tuples", fast_project_tuples, METH_VARARGS,
     "project_tuples(rows, idx) -> [tuple(row[j] for j in idx), ...]"},
    {"rezip", fast_rezip, METH_VARARGS,
     "rezip(deltas, new_rows) -> [(k, new_row, d), ...]"},
    {"filter_deltas", fast_filter_deltas, METH_VARARGS,
     "filter_deltas(deltas, bool_mask) -> kept deltas"},
    {"parse_upserts", fast_parse_upserts, METH_VARARGS,
     "parse_upserts(msgs, start, cols, defaults, base, seq0, mask, ptr) "
     "-> (deltas, new_seq)"},
    {"deliver", fast_deliver, METH_VARARGS,
     "deliver(deltas, time, cb, cols|None): sorted output callbacks"},
    {"capture_apply", fast_capture_apply, METH_VARARGS,
     "capture_apply(rows, updates, deltas, time): one-pass capture sink"},
    {"ref_scalar", fast_ref_scalar, METH_O,
     "ref_scalar(args_tuple) -> Pointer (native blake2b-128 key mint)"},
    {"binop", fast_binop, METH_VARARGS,
     "binop(left, right, code, error_obj, op) -> (out, [(i, msg), ...])"},
    {"parse_pk_upserts", fast_parse_pk_upserts, METH_VARARGS,
     "parse_pk_upserts(dicts, cols, defaults, pkeys, live_rows) -> deltas"},
    {"ref_scalar_v", fast_ref_scalar_v, METH_VARARGS,
     "ref_scalar_v(*args) -> Pointer (variadic native key mint)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "fastpath",
    "Native engine fast path (consolidate/freeze/key bytes).", -1, methods,
};

PyMODINIT_FUNC
PyInit_fastpath(void)
{
    return PyModule_Create(&moduledef);
}
