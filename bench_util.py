"""Shared measurement policy for the bench harness (bench.py and
scripts/bench_relational.py): median-of-runs selection with dispersion
flagging, and the atomic artifact writer. One module so both measurement
planes always report under the same policy."""

from __future__ import annotations

import json
import os
import statistics
import tempfile

DISPERSION_FLAG = 0.2


def dispersion(values: list[float]) -> float:
    med = statistics.median(values)
    return round((max(values) - min(values)) / med, 3) if med else 0.0


def median_index(rates: list[float]) -> int:
    """Index of the run whose rate is the median."""
    return rates.index(sorted(rates)[len(rates) // 2])


def median_of(runs: list[dict], rates: list[float]) -> dict:
    """The run whose rate is the median, annotated with the spread."""
    out = dict(runs[median_index(rates)])
    out["runs"] = [round(r, 1) for r in rates]
    out["dispersion"] = dispersion(rates)
    out["unsteady"] = dispersion(rates) > DISPERSION_FLAG
    return out


def write_artifact_atomic(path: str, artifact: list[dict]) -> None:
    """Rewrite the artifact via temp-file + rename so a crash mid-write
    can never truncate previously recorded metrics. A failed write (full
    disk, permissions) is logged loudly — a silently stale artifact would
    defeat the self-defending-measurement goal — and the temp file is
    cleaned up; the previous artifact version stays intact either way."""
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".bench_full_", suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(artifact, f, indent=1)
        os.replace(tmp, path)
    except OSError as exc:
        import logging

        logging.getLogger(__name__).warning(
            "bench artifact write failed (%s stays stale): %s", path, exc
        )
        try:
            os.unlink(tmp)
        except OSError:
            pass
